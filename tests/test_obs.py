"""Tests for the runtime telemetry subsystem (``veles.simd_tpu.obs``).

Six contracts pinned here:

* the registry is thread-safe and the event log is bounded;
* both export formats (JSON, Prometheus text) round-trip, with correct
  exposition escaping/sanitization and histogram
  ``_bucket``/``_sum``/``_count`` wire format;
* every ``select_algorithm`` threshold boundary records a decision
  event naming the algorithm actually selected;
* spans (the time axis) feed warmup/steady latency histograms, nest,
  export as Perfetto-loadable Chrome trace JSON, and cost ≤5µs per
  dispatch while telemetry is off;
* ``obs.save``/``obs.save_trace`` are atomic — a failed write never
  truncates an existing snapshot;
* telemetry on or off, traced programs are byte-identical — the whole
  layer lives strictly at the Python dispatch layer.
"""

import concurrent.futures
import json
import os
import re
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veles.simd_tpu import obs
from veles.simd_tpu.obs import export as obs_export
from veles.simd_tpu.obs import spans as spans_mod
from veles.simd_tpu.obs.events import DEFAULT_MAX_EVENTS, EventLog
from veles.simd_tpu.obs.registry import MetricsRegistry
from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import spectral as sp
from veles.simd_tpu.ops import wavelet as wv
from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

RNG = np.random.RandomState(0)


@pytest.fixture
def telemetry():
    """Telemetry ON (with the jax.monitoring bridge), clean slate, and a
    guaranteed return to the disabled default afterwards."""
    obs.enable()
    obs.reset()
    yield obs
    obs.disable()
    obs.reset()
    obs.configure(max_events=DEFAULT_MAX_EVENTS,
                  max_spans=spans_mod.DEFAULT_MAX_SPANS)


# --------------------------------------------------------------------------
# registry / event log primitives
# --------------------------------------------------------------------------


def test_registry_thread_safety_under_concurrent_increments():
    reg = MetricsRegistry()
    threads, per_thread = 8, 2000

    def worker(_):
        for _ in range(per_thread):
            reg.count("hammered", op="x")
        return True

    with concurrent.futures.ThreadPoolExecutor(threads) as ex:
        assert all(ex.map(worker, range(threads)))
    assert reg.counter_value("hammered", op="x") == threads * per_thread


def test_obs_facade_thread_safety(telemetry):
    threads, per_thread = 8, 1000
    obs.configure(max_events=threads * per_thread)

    def worker(i):
        for _ in range(per_thread):
            obs.count("facade.hammered")
            obs.record_decision("op", "path", worker=i)
        return True

    with concurrent.futures.ThreadPoolExecutor(threads) as ex:
        assert all(ex.map(worker, range(threads)))
    assert obs.counter_value("facade.hammered") == threads * per_thread
    # every recorded event survived into the (large enough) ring intact
    evs = obs.events()
    assert len(evs) == threads * per_thread
    assert sorted(e["seq"] for e in evs) == list(range(len(evs)))


def test_event_log_bounding():
    log = EventLog(max_events=32)
    for i in range(100):
        log.record("op", "decision", i=i)
    evs = log.events()
    assert len(evs) == 32
    assert log.dropped == 68
    # ring keeps the NEWEST events, oldest-first
    assert [e["i"] for e in evs] == list(range(68, 100))
    assert [e["seq"] for e in evs] == list(range(68, 100))


def test_event_log_bounding_through_facade(telemetry):
    obs.configure(max_events=16)
    for i in range(50):
        obs.record_decision("op", "d", i=i)
    snap = obs.snapshot()
    assert len(snap["events"]) == 16
    assert snap["events_dropped"] == 34
    # aggregates survive the wraparound
    assert obs.counter_value("decisions", op="op", decision="d") == 50


def test_disabled_records_nothing():
    obs.disable()
    obs.reset()
    obs.count("should.not.exist")
    obs.record_decision("op", "d")
    obs.observe("hist", 0.5)
    obs.gauge("g", 1.0)
    with obs.span("should.not.exist.either"):
        pass
    snap = obs.snapshot()
    assert snap["counters"] == []
    assert snap["events"] == []
    assert snap["histograms"] == []
    assert snap["gauges"] == []
    assert snap["enabled"] is False
    # no span trace events either (only the process-name metadata row)
    assert all(e["ph"] == "M" for e in obs.trace_events())


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------


def _populated_snapshot():
    obs.count("dispatch", 3, op="convolve", backend="xla")
    obs.count("dispatch", op="convolve", backend="oracle")
    obs.gauge("mesh.devices", 8.0)
    obs.observe("compile.backend_compile_secs", 0.025)
    obs.observe("compile.backend_compile_secs", 2.5)
    obs.record_decision("convolve", "overlap_save",
                        x_length=1 << 20, h_length=2047)
    return obs.snapshot()


def test_json_export_round_trip(telemetry):
    snap = _populated_snapshot()
    assert obs_export.from_json(obs.to_json(snap)) == snap
    # strict JSON (bench artifacts use allow_nan=False)
    json.loads(obs.to_json(snap))


def test_json_save_load_round_trip(telemetry, tmp_path):
    snap = _populated_snapshot()
    path = obs.save(str(tmp_path / "snap.json"), snap)
    assert obs.load(path) == snap


def test_prometheus_export_round_trip(telemetry):
    snap = _populated_snapshot()
    text = obs.to_prometheus(snap)
    parsed = obs_export.parse_prometheus(text)
    # every counter and gauge sample is recoverable with its value
    for c in snap["counters"]:
        key = (obs_export.PROMETHEUS_PREFIX
               + c["name"].replace(".", "_") + "_total",
               tuple(sorted(c["labels"].items())))
        assert parsed[key] == c["value"], key
    for g in snap["gauges"]:
        key = (obs_export.PROMETHEUS_PREFIX
               + g["name"].replace(".", "_"),
               tuple(sorted(g["labels"].items())))
        assert parsed[key] == g["value"]
    # histogram series: cumulative buckets, sum and count
    hist = snap["histograms"][0]
    hname = (obs_export.PROMETHEUS_PREFIX
             + hist["name"].replace(".", "_"))
    assert parsed[(hname + "_count", ())] == hist["count"] == 2
    assert parsed[(hname + "_sum", ())] == pytest.approx(hist["sum"])
    assert parsed[(hname + "_bucket", (("le", "+Inf"),))] == 2


def test_report_renders(telemetry):
    snap = _populated_snapshot()
    text = obs.report(snap)
    assert "overlap_save" in text
    assert "dispatch{backend=xla,op=convolve}" in text


# --------------------------------------------------------------------------
# spans: the time axis
# --------------------------------------------------------------------------


def test_span_feeds_histogram_with_warmup_then_steady(telemetry):
    for _ in range(3):
        with obs.span("unit.op", algo="fft"):
            pass
    hists = {(h["name"], h["labels"].get("phase")): h
             for h in obs.snapshot()["histograms"]}
    assert hists[("span.unit.op", "warmup")]["count"] == 1
    assert hists[("span.unit.op", "steady")]["count"] == 2
    # attrs travel into trace args ONLY — never histogram labels
    for h in hists.values():
        assert set(h["labels"]) == {"phase"}


def test_span_warmup_is_per_attr_class(telemetry):
    # a NEW route through the same span name compiles its own
    # executable — it gets its own warmup mark, not a steady mislabel
    with obs.span("routed.op", route="a"):
        pass
    with obs.span("routed.op", route="b"):
        pass
    with obs.span("routed.op", route="a"):
        pass
    phases = [e["args"]["phase"] for e in obs.trace_events()
              if e["ph"] == "X"]
    assert phases == ["warmup", "warmup", "steady"]


def test_span_reset_restores_warmup(telemetry):
    with obs.span("unit.reset"):
        pass
    obs.reset()
    with obs.span("unit.reset"):
        pass
    hists = {(h["name"], h["labels"].get("phase")): h["count"]
             for h in obs.snapshot()["histograms"]}
    assert hists == {("span.unit.reset", "warmup"): 1}


def test_span_nesting_records_parent(telemetry):
    with obs.span("outer.op"):
        with obs.span("inner.op"):
            pass
    by_name = {e["name"]: e for e in obs.trace_events()
               if e["ph"] == "X"}
    assert by_name["inner.op"]["args"]["parent"] == "outer.op"
    assert "parent" not in by_name["outer.op"]["args"]
    # the child completes inside the parent's interval
    outer, inner = by_name["outer.op"], by_name["inner.op"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] \
        + 1e-3


def test_span_trace_buffer_bounded(telemetry):
    obs.configure(max_spans=8)
    for i in range(20):
        with obs.span("bounded.op", i=i):
            pass
    events = [e for e in obs.trace_events() if e["ph"] == "X"]
    assert len(events) == 8
    assert [e["args"]["i"] for e in events] == list(range(12, 20))
    assert obs.snapshot()["spans_dropped"] == 12
    # the drop signal reaches both exporters, not just the raw snapshot
    assert "veles_simd_spans_dropped_total 12" in obs.to_prometheus()
    assert "spans dropped" in obs.report()
    obs.configure(max_spans=32768)


def test_span_reserved_args_not_clobbered_by_attrs(telemetry):
    with obs.span("clobber.op", phase="forward", parent="fake"):
        pass
    ev = [e for e in obs.trace_events() if e["ph"] == "X"][-1]
    assert ev["args"]["phase"] == "warmup"       # tag wins over attr
    assert "parent" not in ev["args"]            # top-level span


def test_save_trace_is_perfetto_loadable_structurally(telemetry,
                                                      tmp_path):
    with obs.span("a.op", algo="x"):
        with obs.span("b.op"):
            pass
    with obs.span("a.op", algo="x"):
        pass
    path = obs.save_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)          # strict JSON
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    xs = [e for e in events if e["ph"] == "X"]
    assert len(xs) == 3
    # only complete X events and metadata M events; every X carries
    # ts/dur/pid/tid and ts is monotonic within the file
    assert {e["ph"] for e in events} <= {"X", "M"}
    for e in xs:
        assert e["dur"] >= 0
        assert e["pid"] == os.getpid()
        assert isinstance(e["tid"], int)
        assert e["args"]["phase"] in ("warmup", "steady")
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    assert all(t >= 0 for t in ts)


def test_span_disabled_overhead_under_5us():
    obs.disable()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        with obs.span("overhead.probe", algo="fft"):
            pass
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 5e-6, f"disabled span cost {per_call:.2e}s"
    # and the disabled path returns one shared object (no allocation)
    assert obs.span("x") is obs.span("y")


def test_span_exception_still_recorded(telemetry):
    with pytest.raises(RuntimeError):
        with obs.span("exc.op"):
            raise RuntimeError("boom")
    hists = {h["name"] for h in obs.snapshot()["histograms"]}
    assert "span.exc.op" in hists


def test_span_xla_trace_bridge_flag():
    from veles.simd_tpu.obs import spans as spans_mod

    assert spans_mod.xla_trace_active() is False
    try:
        spans_mod.set_xla_trace_active(True)
        assert spans_mod.xla_trace_active() is True
        obs.enable()
        obs.reset()
        # TraceAnnotation outside a live XLA trace session is a no-op
        # scope — the span must still complete and record
        with obs.span("bridged.op"):
            pass
        assert any(e["name"] == "bridged.op"
                   for e in obs.trace_events())
    finally:
        spans_mod.set_xla_trace_active(False)
        obs.disable()
        obs.reset()


def test_wired_dispatch_records_spans(telemetry):
    x = RNG.randn(4096).astype(np.float32)
    h = RNG.randn(64).astype(np.float32)
    cv.convolve(x, h, simd=True)
    names = {h_["name"] for h_ in obs.snapshot()["histograms"]}
    assert "span.convolve.dispatch" in names
    assert "span.convolve.os_route" in names
    sp.stft(RNG.randn(2048).astype(np.float32), 256, 64, simd=True)
    names = {h_["name"] for h_ in obs.snapshot()["histograms"]}
    assert "span.stft.dispatch" in names


# --------------------------------------------------------------------------
# atomic snapshot/trace writes
# --------------------------------------------------------------------------


def test_save_is_atomic_on_serialization_failure(telemetry, tmp_path):
    path = str(tmp_path / "snap.json")
    obs.count("keep.me")
    obs.save(path)
    good = open(path).read()
    with pytest.raises(TypeError):
        obs.save(path, {"unserializable": object()})
    assert open(path).read() == good       # old snapshot intact
    assert os.listdir(tmp_path) == ["snap.json"]  # no tmp litter


def test_save_trace_leaves_no_tmp_files(telemetry, tmp_path):
    with obs.span("t.op"):
        pass
    obs.save_trace(str(tmp_path / "trace.json"))
    obs.save_trace(str(tmp_path / "trace.json"))  # overwrite path too
    assert os.listdir(tmp_path) == ["trace.json"]


# --------------------------------------------------------------------------
# Prometheus exposition correctness
# --------------------------------------------------------------------------


def test_prometheus_label_value_escaping(telemetry):
    # incl. the order-of-unescape trap: a literal backslash followed
    # by a literal 'n' must NOT come back as a newline
    nasty = 'he said "hi"\\path\nnext C:\\nasty'
    obs.count("escaped", who=nasty)
    text = obs.to_prometheus()
    # exposition line stays one physical line
    line = [ln for ln in text.splitlines()
            if ln.startswith("veles_simd_escaped_total")]
    assert len(line) == 1
    assert r"\"hi\"" in line[0] and r"\n" in line[0]
    parsed = obs_export.parse_prometheus(text)
    assert parsed[("veles_simd_escaped_total",
                   (("who", nasty),))] == 1


def test_prometheus_metric_name_sanitization(telemetry):
    obs.count("span.weird-name 1")
    obs.gauge("mesh.devices/total", 4)
    text = obs.to_prometheus()
    assert "veles_simd_span_weird_name_1_total 1" in text
    assert "veles_simd_mesh_devices_total 4.0" in text
    # every emitted sample name is exposition-legal
    for (name, _labels) in obs_export.parse_prometheus(text):
        assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name), name


def test_prometheus_histogram_wire_format(telemetry):
    from veles.simd_tpu.obs.registry import DEFAULT_BUCKETS

    samples = [5e-7, 2e-6, 2e-6, 0.5, 100.0]
    for s in samples:
        obs.observe("lat", s, op="x")
    text = obs.to_prometheus()
    lines = [ln for ln in text.splitlines()
             if ln.startswith("veles_simd_lat")]
    # TYPE comment filtered out above; series = buckets + Inf + sum/count
    bucket_lines = [ln for ln in lines if "_bucket" in ln]
    assert len(bucket_lines) == len(DEFAULT_BUCKETS) + 1
    parsed = obs_export.parse_prometheus(text)

    def bucket(le):
        return parsed[("veles_simd_lat_bucket",
                       (("le", le), ("op", "x")))]

    # cumulative counts at the interesting boundaries
    assert bucket(repr(1e-6)) == 1        # the 5e-7 sample
    assert bucket(repr(3e-6)) == 3        # + two 2e-6 samples
    assert bucket(repr(1.0)) == 4         # + the 0.5 sample
    assert bucket("+Inf") == 5            # + the out-of-range 100.0
    assert parsed[("veles_simd_lat_count", (("op", "x"),))] == 5
    assert parsed[("veles_simd_lat_sum", (("op", "x"),))] == \
        pytest.approx(sum(samples))
    # cumulative monotonicity across the whole bucket ladder
    les = [repr(b) for b in DEFAULT_BUCKETS] + ["+Inf"]
    counts = [bucket(le) for le in les]
    assert counts == sorted(counts)


def test_histogram_quantiles_interpolate(telemetry):
    for _ in range(90):
        obs.observe("q", 2e-6)            # (1e-6, 3e-6] bucket
    for _ in range(10):
        obs.observe("q", 2e-3)            # (1e-3, 3e-3] bucket
    h = [h_ for h_ in obs.snapshot()["histograms"]
         if h_["name"] == "q"][0]
    qs = obs_export.histogram_quantiles(h)
    assert 1e-6 <= qs["p50"] <= 3e-6
    assert 1e-3 <= qs["p99"] <= 3e-3
    # p95 sits exactly at the bucket boundary rank: 95th of 100 lands
    # mid-ladder, still inside the second bucket's bounds
    assert 1e-6 <= qs["p95"] <= 3e-3
    assert obs_export.histogram_quantile({"count": 0, "buckets": {}},
                                         0.5) is None


# --------------------------------------------------------------------------
# decision events at the select_algorithm threshold boundaries
# --------------------------------------------------------------------------

BF = cv.ConvolutionAlgorithm.BRUTE_FORCE
FFT = cv.ConvolutionAlgorithm.FFT
OS = cv.ConvolutionAlgorithm.OVERLAP_SAVE

# (x_length, h_length) straddling both thresholds:
# product boundary x*h = AUTO_FFT_MIN_PRODUCT (8192) and
# ratio boundary x = AUTO_OVERLAP_SAVE_MIN_RATIO * h (8h)
BOUNDARY_CASES = [
    (127, 64, BF),       # 8128 < 8192: latency floor
    (128, 64, FFT),      # 8192 hits the product threshold, ratio 2
    (8191, 1, BF),       # one under the product threshold
    (8192, 1, OS),       # at threshold AND ratio 8192 >= 8
    (1023, 128, FFT),    # ratio just under 8
    (1024, 128, OS),     # ratio exactly 8
    (1025, 128, OS),     # ratio just over 8
    (4096, 4096, FFT),   # large balanced problem
]


@pytest.mark.parametrize("x_len,h_len,expect", BOUNDARY_CASES)
def test_decision_event_at_threshold_boundary(telemetry, x_len, h_len,
                                              expect):
    assert cv.select_algorithm(x_len, h_len) is expect
    handle = cv.convolve_initialize(x_len, h_len)
    assert handle.algorithm is expect
    ev = obs.events()[-1]
    assert ev["op"] == "convolve"
    assert ev["decision"] == expect.value
    assert ev["x_length"] == x_len and ev["h_length"] == h_len
    assert ev["forced"] is False
    if expect is OS:
        assert ev["block_length"] == handle.block_length
        assert ev["step"] == handle.step
    if expect is FFT:
        assert ev["fft_length"] == handle.fft_length


def test_forced_algorithm_flagged(telemetry):
    cv.convolve_initialize(100, 50, cv.ConvolutionAlgorithm.FFT)
    ev = obs.events()[-1]
    assert ev["decision"] == "fft" and ev["forced"] is True


# --------------------------------------------------------------------------
# dispatch-surface wiring
# --------------------------------------------------------------------------


def test_dispatch_counters_per_backend(telemetry):
    x, h = RNG.randn(64).astype(np.float32), np.ones(4, np.float32)
    cv.convolve(x, h, simd=True)
    cv.convolve(x, h, simd=False)
    assert obs.counter_value("dispatch", op="convolve",
                             backend="xla") == 1
    assert obs.counter_value("dispatch", op="convolve",
                             backend="oracle") == 1


def test_stft_istft_framing_decisions(telemetry):
    x = RNG.randn(2048).astype(np.float32)
    sp.stft(x, 256, 64, simd=True)           # 256 % 64 == 0, r=4
    assert obs.events()[-1]["op"] == "stft"
    assert obs.events()[-1]["decision"] == "reshape_interleave"
    sp.stft(x, 256, 96, simd=True)           # non-dividing hop
    assert obs.events()[-1]["decision"] == "gather"
    spec = sp.stft(x, 256, 64, simd=True)
    sp.istft(spec, 2048, 256, 64, simd=True)
    assert obs.events()[-1]["op"] == "istft"
    assert obs.events()[-1]["decision"] == "reshape_overlap_add"


def test_wavelet_decisions(telemetry):
    x = RNG.randn(4, 256).astype(np.float32)
    wv.wavelet_apply(WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC,
                     x, simd=True)
    ev = obs.events()[-1]
    assert ev["op"] == "wavelet_apply"
    assert ev["decision"] in ("pallas", "xla_conv")
    assert ev["family"] == "daub" and ev["order"] == 8
    wv.wavelet_transform(WaveletType.DAUBECHIES, 4,
                         wv.ExtensionType.PERIODIC, x, 2, simd=True)
    evs = [e for e in obs.events() if e["op"] == "wavelet_transform"]
    assert evs[-1]["decision"] in ("level_loop", "fused_cascade")
    assert evs[-1]["levels"] == 2


def test_sharded_convolve_geometry_event(telemetry):
    from veles.simd_tpu.parallel import mesh as pm
    from veles.simd_tpu.parallel import ops as pops

    mesh = pm.default_mesh("sp")
    x = RNG.randn(1024).astype(np.float32)
    h = RNG.randn(17).astype(np.float32)
    pops.sharded_convolve(x, h, mesh, axis="sp")
    evs = [e for e in obs.events() if e["op"] == "sharded_convolve"]
    assert evs[-1]["decision"] == "one_hop_halo"
    assert evs[-1]["n_shards"] == mesh.shape["sp"]
    assert evs[-1]["halo"] == 16


# --------------------------------------------------------------------------
# the traced-program contract: telemetry must be invisible to XLA
# --------------------------------------------------------------------------


def _convolve_jaxpr():
    x = jnp.zeros(300, jnp.float32)
    h = jnp.zeros(30, jnp.float32)
    return str(jax.make_jaxpr(lambda a, b: cv.convolve(a, b))(x, h))


def _stft_jaxpr():
    x = jnp.zeros(1024, jnp.float32)
    return str(jax.make_jaxpr(
        lambda a: sp.stft(a, 128, 32, simd=True))(x))


@pytest.mark.parametrize("build", [_convolve_jaxpr, _stft_jaxpr],
                         ids=["convolve", "stft"])
def test_jaxpr_identical_with_telemetry_on_and_off(build):
    obs.disable()
    obs.reset()
    jaxpr_off = build()
    obs.enable()
    try:
        jaxpr_on = build()
        assert obs.events(), "telemetry was on but recorded nothing"
        # spans fired at the dispatch layer during tracing — and still
        # left the jaxpr untouched (asserted below)
        assert any(e["ph"] == "X" for e in obs.trace_events()), \
            "telemetry was on but no span completed"
    finally:
        obs.disable()
        obs.reset()
    assert jaxpr_off == jaxpr_on


# --------------------------------------------------------------------------
# acceptance: a 1M-point convolve under telemetry tells the whole story
# --------------------------------------------------------------------------


def test_1m_convolve_snapshot_names_algorithm_and_compiles(telemetry):
    n, k = 1 << 20, 2049
    x = RNG.randn(n).astype(np.float32)
    h = RNG.randn(k).astype(np.float32)
    y = cv.convolve(x, h, simd=True)
    np.asarray(y[-1:])  # force execution
    snap = obs.snapshot()
    ev = [e for e in snap["events"] if e["op"] == "convolve"][-1]
    assert ev["decision"] == "overlap_save"       # x >= 8h
    assert ev["x_length"] == n and ev["h_length"] == k
    assert obs.counter_value("dispatch", op="convolve",
                             backend="xla") >= 1
    # the jax.monitoring bridge saw the backend compile
    assert obs.counter_value("compile.backend_compile") >= 1
    hists = {h_["name"] for h_ in snap["histograms"]}
    assert "compile.backend_compile_secs" in hists
    # exportable both ways, naming the selected algorithm
    as_json = obs.to_json(snap)
    assert "overlap_save" in as_json
    parsed = obs_export.parse_prometheus(obs.to_prometheus(snap))
    assert parsed[("veles_simd_decisions_total",
                   (("decision", "overlap_save"),
                    ("op", "convolve")))] >= 1


# --------------------------------------------------------------------------
# the resource axis: instrumented compile sites
# --------------------------------------------------------------------------


def _probe_fn(a, b):
    return a @ b + 1.0


def test_instrumented_jit_passthrough_when_disabled():
    obs.disable()
    obs.reset()
    fn = obs.instrumented_jit(_probe_fn, op="probe", route="r")
    x = jnp.ones((32, 32), jnp.float32)
    np.testing.assert_allclose(np.asarray(fn(x, x)),
                               np.asarray(x @ x + 1.0), rtol=1e-6)
    assert obs.resources() == []        # nothing harvested while off


def test_instrumented_jit_harvests_cost_and_memory(telemetry):
    fn = obs.instrumented_jit(_probe_fn, op="probe", route="matmul")
    x = jnp.ones((32, 32), jnp.float32)
    fn(x, x)
    entries = [e for e in obs.resources() if e["op"] == "probe"]
    assert len(entries) == 1
    e = entries[0]
    assert e["route"] == "matmul"
    assert e["flops"] and e["flops"] > 0
    assert e["bytes_accessed"] and e["bytes_accessed"] > 0
    assert e["arith_intensity"] == pytest.approx(
        e["flops"] / e["bytes_accessed"])
    # CPU backend reports full memory stats; the breakdown keys are
    # always present (None when a backend cannot report them)
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "generated_code_bytes", "peak_bytes"):
        assert key in e
    assert e["argument_bytes"] == 2 * 32 * 32 * 4
    assert e["output_bytes"] == 32 * 32 * 4
    assert "float32[32,32]" in e["shapes"]
    assert e["analyses"] == 1


def test_instrumented_jit_memoizes_per_geometry(telemetry):
    fn = obs.instrumented_jit(_probe_fn, op="probe2", route="r")
    x = jnp.ones((16, 16), jnp.float32)
    fn(x, x)
    fn(x, x)                            # same geometry: memo hit
    e = [e for e in obs.resources() if e["op"] == "probe2"][0]
    assert e["analyses"] == 1
    y = jnp.ones((8, 8), jnp.float32)
    fn(y, y)                            # new geometry: re-harvested
    e = [e for e in obs.resources() if e["op"] == "probe2"][0]
    assert e["analyses"] == 2
    assert "float32[8,8]" in e["shapes"]    # latest geometry wins
    memo = obs.caches()["obs_analysis_memo"]
    assert memo["hits"] >= 1 and memo["misses"] >= 2


def test_instrumented_jit_skips_harvest_under_outer_trace(telemetry):
    fn = obs.instrumented_jit(_probe_fn, op="traced_probe", route="r")

    @jax.jit
    def outer(v):
        return fn(v, v)

    outer(jnp.ones((8, 8), jnp.float32))
    # tracer args cannot be lowered eagerly: no harvest, no crash
    assert not any(e["op"] == "traced_probe" for e in obs.resources())


def test_instrumented_jit_static_argnames_and_decorator(telemetry):
    import functools

    @functools.partial(obs.instrumented_jit, op="probe3",
                       static_argnames=("k",))
    def scaled(a, k):
        return a * k

    out = scaled(jnp.ones(128, jnp.float32), k=3)
    np.testing.assert_allclose(np.asarray(out), 3.0)
    assert any(e["op"] == "probe3" for e in obs.resources())


def test_convolve_routes_land_in_resources(telemetry):
    x = RNG.randn(1 << 14).astype(np.float32)
    h = RNG.randn(255).astype(np.float32)
    handle = cv.convolve_overlap_save_initialize(len(x), len(h))
    np.asarray(cv.convolve_overlap_save(handle, x, h, simd=True)[:1])
    routes = {(e["op"], e["route"]) for e in obs.resources()}
    assert ("convolve", "os_matmul") in routes
    e = [e for e in obs.resources()
         if (e["op"], e["route"]) == ("convolve", "os_matmul")][0]
    # the blocked matmul must account at least the useful MAC volume
    assert e["flops"] >= 2 * len(h) * len(x)


def test_resources_round_trip_and_prometheus(telemetry):
    fn = obs.instrumented_jit(_probe_fn, op="probe4", route="r")
    x = jnp.ones((16, 16), jnp.float32)
    fn(x, x)
    snap = obs.snapshot()
    assert snap["resources"]
    assert obs_export.from_json(obs.to_json(snap)) == snap
    text = obs.to_prometheus(snap)
    parsed = obs_export.parse_prometheus(text)
    key = ("veles_simd_resource_flops", (("op", "probe4"),
                                         ("route", "r")))
    assert parsed[key] > 0
    assert ("veles_simd_cache_size",
            (("cache", "obs_analysis_memo"),)) in parsed
    rep = obs.report(snap)
    assert "compiled-program resources" in rep
    assert "probe4/r" in rep
    assert "compile caches:" in rep


def test_reset_clears_resources(telemetry):
    fn = obs.instrumented_jit(_probe_fn, op="probe5", route="r")
    x = jnp.ones((8, 8), jnp.float32)
    fn(x, x)
    assert obs.resources()
    obs.reset()
    assert obs.resources() == []
    memo = obs.caches()["obs_analysis_memo"]
    assert memo["size"] == 0 and memo["misses"] == 0


# --------------------------------------------------------------------------
# unified cache introspection
# --------------------------------------------------------------------------


def test_caches_unified_snapshot(telemetry):
    from veles.simd_tpu.ops import batched
    from veles.simd_tpu.ops import convolve2d  # noqa: F401 — its
    # import registers the pallas2d OOM cache provider

    batched.clear_handle_cache()
    sos = np.array([[0.2, 0.1, 0.0, 1.0, -0.3, 0.0]], np.float32)
    xs = RNG.randn(4, 256).astype(np.float32)
    batched.batched_sosfilt(sos, xs)        # miss (compile)
    batched.batched_sosfilt(sos, xs)        # hit
    caches = obs.caches()
    lru = caches["batched_handle_lru"]
    assert lru["size"] == 1
    assert lru["capacity"] == batched.BATCHED_CACHE_MAXSIZE
    assert lru["hits"] >= 1 and lru["misses"] >= 1
    assert "pallas2d_oom_rejected" in caches
    assert caches["pallas2d_oom_rejected"]["capacity"] == 256
    assert "pallas_os_rejected" in caches
    assert "obs_analysis_memo" in caches
    # JSON-native all the way down (tuples would break round trips)
    json.dumps(caches, allow_nan=False)
    batched.clear_handle_cache()


def test_cache_provider_error_is_contained(telemetry):
    import sys

    # NB: the obs facade function `obs.resources` shadows the
    # submodule on from-import; go through sys.modules for the module
    res_mod = sys.modules["veles.simd_tpu.obs.resources"]

    def bad():
        raise RuntimeError("provider exploded")

    obs.register_cache("exploding", bad)
    try:
        caches = obs.caches()
        assert "provider exploded" in caches["exploding"]["error"]
    finally:
        with res_mod._cache_lock:
            res_mod._cache_providers.pop("exploding", None)


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------


@pytest.fixture
def flight(telemetry, tmp_path):
    """Telemetry on + flight dir pointed at tmp + a re-armed auto
    budget; restores the env lookup afterwards."""
    from veles.simd_tpu.obs import flightrec

    obs.configure(flight_dir=str(tmp_path))
    flightrec._reset_auto_count()
    yield tmp_path
    obs.configure(flight_dir="")
    flightrec._reset_auto_count()


def test_dump_debug_bundle_explicit_path(telemetry, tmp_path):
    obs.count("bundle.probe")
    with obs.span("bundle.span"):
        pass
    path = obs.dump_debug_bundle(str(tmp_path / "b.json"),
                                 reason="unit")
    with open(path) as f:
        doc = json.load(f)          # strict JSON
    assert doc["schema"] == "veles-simd-flight-v1"
    assert doc["reason"] == "unit"
    assert doc["exception"] is None
    assert doc["platform"]["pid"] == os.getpid()
    assert "conv_precision" in doc["config"]
    names = [c["name"] for c in doc["snapshot"]["counters"]]
    assert "bundle.probe" in names
    assert any(e.get("name") == "bundle.span"
               for e in doc["trace_events"])
    assert "caches" in doc["snapshot"]
    assert "resources" in doc["snapshot"]
    assert os.listdir(tmp_path) == ["b.json"]   # atomic, no litter


def test_dump_debug_bundle_default_dir(flight):
    path = obs.dump_debug_bundle(reason="default_dir")
    assert os.path.dirname(path) == str(flight)
    assert os.path.basename(path).startswith("flight-")
    json.load(open(path))


def test_crash_in_top_level_span_writes_bundle(flight):
    with pytest.raises(RuntimeError):
        with obs.span("crash.outer"):
            with obs.span("crash.inner"):
                raise RuntimeError("dispatch exploded")
    bundles = [f for f in os.listdir(flight)
               if f.startswith("flight-")]
    assert len(bundles) == 1        # inner span (nested) didn't double
    doc = json.load(open(os.path.join(flight, bundles[0])))
    assert doc["reason"] == "span_crash"
    assert doc["exception"]["type"] == "RuntimeError"
    assert "dispatch exploded" in doc["exception"]["message"]
    assert any("dispatch exploded" in line
               for line in doc["exception"]["traceback"])


def test_crash_bundles_rate_limited(flight):
    from veles.simd_tpu.obs import flightrec

    for i in range(flightrec.MAX_AUTO_BUNDLES + 2):
        with pytest.raises(ValueError):
            with obs.span("crash.repeat", i=i):
                raise ValueError("again")
    bundles = [f for f in os.listdir(flight)
               if f.startswith("flight-")]
    assert len(bundles) == flightrec.MAX_AUTO_BUNDLES
    assert flightrec.auto_bundles_written() == \
        flightrec.MAX_AUTO_BUNDLES


def test_crash_without_flight_dir_writes_nothing(telemetry, tmp_path,
                                                 monkeypatch):
    from veles.simd_tpu.obs import flightrec

    monkeypatch.delenv(flightrec.FLIGHT_DIR_ENV, raising=False)
    obs.configure(flight_dir="")    # env lookup, which is unset
    flightrec._reset_auto_count()
    with pytest.raises(RuntimeError):
        with obs.span("crash.unarmed"):
            raise RuntimeError("no dir")
    assert flightrec.auto_bundles_written() == 0
    assert os.listdir(tmp_path) == []


def test_flight_dir_env_arming(telemetry, tmp_path, monkeypatch):
    from veles.simd_tpu.obs import flightrec

    monkeypatch.setenv(flightrec.FLIGHT_DIR_ENV, str(tmp_path))
    obs.configure(flight_dir="")    # defer to the env var
    flightrec._reset_auto_count()
    try:
        with pytest.raises(RuntimeError):
            with obs.span("crash.env"):
                raise RuntimeError("env armed")
        assert len(os.listdir(tmp_path)) == 1
    finally:
        flightrec._reset_auto_count()


# --------------------------------------------------------------------------
# the jax.monitoring duration/counter bridge (obs/compile.py)
# --------------------------------------------------------------------------


def test_monitoring_event_counter_bridge(telemetry):
    import jax.monitoring

    from veles.simd_tpu.obs import compile as obs_compile

    obs.install_compile_listeners()
    before = obs.counter_value("compile.cache_hits")
    jax.monitoring.record_event("/jax/compilation_cache/cache_hits")
    assert obs.counter_value("compile.cache_hits") == before + 1
    # unknown events fall through without counting anything
    jax.monitoring.record_event("/jax/unrelated/event")
    snap_names = {c["name"] for c in obs.snapshot()["counters"]}
    assert not any("unrelated" in n for n in snap_names)
    # every mapped event name is wired
    for event, counter in obs_compile.EVENT_COUNTERS.items():
        base = obs.counter_value(counter)
        jax.monitoring.record_event(event)
        assert obs.counter_value(counter) == base + 1


def test_monitoring_duration_bridge(telemetry):
    import jax.monitoring

    from veles.simd_tpu.obs import compile as obs_compile

    obs.install_compile_listeners()
    obs.reset()
    jax.monitoring.record_event_duration_secs(
        "/jax/core/compile/backend_compile_duration", 0.125)
    jax.monitoring.record_event_duration_secs(
        "/jax/core/compile/jaxpr_trace_duration", 0.25)
    assert obs.counter_value("compile.backend_compile") == 1
    hists = {h["name"]: h for h in obs.snapshot()["histograms"]}
    bc = hists["compile.backend_compile_secs"]
    assert bc["count"] == 1
    assert bc["sum"] == pytest.approx(0.125)
    # counter-less duration metrics feed ONLY their histogram
    tr = hists["compile.jaxpr_trace_secs"]
    assert tr["count"] == 1 and tr["sum"] == pytest.approx(0.25)
    assert obs.counter_value("compile.jaxpr_trace") == 0
    # every mapped duration metric lands in its histogram
    for event, (_c, hist) in obs_compile.DURATION_METRICS.items():
        jax.monitoring.record_event_duration_secs(event, 1e-3)
    hists = {h["name"]: h for h in obs.snapshot()["histograms"]}
    for _event, (_c, hist) in obs_compile.DURATION_METRICS.items():
        assert hists[hist]["count"] >= 1


def test_disabled_monitoring_bridge_is_silent():
    import jax.monitoring

    obs.install_compile_listeners()
    obs.disable()
    obs.reset()
    jax.monitoring.record_event("/jax/compilation_cache/cache_hits")
    jax.monitoring.record_event_duration_secs(
        "/jax/core/compile/backend_compile_duration", 0.5)
    assert obs.counter_value("compile.cache_hits") == 0
    assert obs.snapshot()["histograms"] == []


def test_instrumented_jit_scalar_sweep_analyzes_once(telemetry):
    # a wrapper WITHOUT statics treats Python scalars as dynamic
    # weak-typed operands (one executable per TYPE), so a value sweep
    # must not re-run the AOT harvest per value
    fn = obs.instrumented_jit(lambda a, g: a * g, op="probe_scalar")
    x = jnp.ones(64, jnp.float32)
    for gain in (0.5, 0.6, 0.7, 0.8):
        fn(x, gain)
    e = [e for e in obs.resources() if e["op"] == "probe_scalar"][0]
    assert e["analyses"] == 1
    # ...while a wrapper WITH statics keys per static value, matching
    # jax.jit's own compile behavior
    import functools

    @functools.partial(obs.instrumented_jit, op="probe_static",
                       static_argnames=("k",))
    def scaled(a, k):
        return a * k

    scaled(x, k=2)
    scaled(x, k=3)
    e = [e for e in obs.resources() if e["op"] == "probe_static"][0]
    assert e["analyses"] == 2


def test_instrumented_jit_distinct_closures_both_harvested(telemetry):
    # two wrappers sharing (op, route) but baking different constants
    # into their closures compile different programs: the per-instance
    # memo token must keep both harvests (regression: a shared
    # (op, route, shapes) key let the second closure's program hide)
    def build(n_iters):
        def run(a):
            for _ in range(n_iters):
                a = jnp.tanh(a) + a     # not foldable: work scales
            return a
        return obs.instrumented_jit(run, op="probe_closure",
                                    route="batched")

    x = jnp.ones(32, jnp.float32)
    build(1)(x)
    e = [e for e in obs.resources() if e["op"] == "probe_closure"][0]
    first = (e["flops"], e["transcendentals"])
    build(8)(x)         # same shapes, different program
    e = [e for e in obs.resources() if e["op"] == "probe_closure"][0]
    assert e["analyses"] == 2
    assert (e["flops"], e["transcendentals"]) != first


def test_crash_bundle_write_failure_releases_budget(telemetry,
                                                    tmp_path):
    from veles.simd_tpu.obs import flightrec

    # a FILE where the flight dir should be: makedirs fails, the
    # bundle cannot be written — the reserved budget slot must be
    # released so the recorder stays armed once the path is fixed
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("x")
    obs.configure(flight_dir=str(blocker))
    flightrec._reset_auto_count()
    try:
        with pytest.raises(RuntimeError):
            with obs.span("crash.badfs"):
                raise RuntimeError("boom")
        assert flightrec.auto_bundles_written() == 0
        # point at a real dir: the very next crash records normally
        obs.configure(flight_dir=str(tmp_path))
        with pytest.raises(RuntimeError):
            with obs.span("crash.goodfs"):
                raise RuntimeError("boom2")
        assert flightrec.auto_bundles_written() == 1
        assert [f for f in os.listdir(tmp_path)
                if f.startswith("flight-")]
    finally:
        obs.configure(flight_dir="")
        flightrec._reset_auto_count()


# --------------------------------------------------------------------------
# the fleet axis (obs v5): time series, typed signals, trace stitching
# --------------------------------------------------------------------------

from veles.simd_tpu.obs import timeseries as ts  # noqa: E402


def test_histogram_quantile_all_overflow(telemetry):
    # every sample above the top finite bucket: the quantile clamps to
    # the HIGHEST finite bound (30.0 for DEFAULT_BUCKETS) rather than
    # inventing a value inside +Inf — the honest answer a bounded
    # ladder can give, and the one obs.signals consumers must expect
    from veles.simd_tpu.obs.registry import DEFAULT_BUCKETS

    for _ in range(7):
        obs.observe("overflow_only", 1e6)
    h = [h_ for h_ in obs.snapshot()["histograms"]
         if h_["name"] == "overflow_only"][0]
    assert h["buckets"]["+Inf"] == 7
    assert all(h["buckets"][repr(b)] == 0 for b in DEFAULT_BUCKETS)
    top = max(DEFAULT_BUCKETS)
    for q in (0.5, 0.95, 0.99):
        assert obs_export.histogram_quantile(h, q) == \
            pytest.approx(top)


class TestFleetSeries:
    def test_ring_is_bounded_and_derivatives_window(self):
        fs = ts.FleetSeries(window=4)
        for i in range(10):
            fs.record("r0", "depth", float(i), t_s=float(i))
        assert len(fs.samples("r0", "depth")) == 4
        # the window holds the LAST 4 samples: 6..9
        assert fs.value("r0", "depth") == 9.0
        assert fs.delta("r0", "depth") == pytest.approx(3.0)
        assert fs.rate("r0", "depth") == pytest.approx(1.0)
        with pytest.raises(ValueError):
            ts.FleetSeries(window=1)

    def test_derivative_functions_on_short_series(self):
        assert ts.delta([]) is None
        assert ts.rate([(1.0, 5.0)]) is None
        assert ts.rate([(1.0, 5.0), (1.0, 9.0)]) is None  # dt == 0
        assert ts.ewma([]) is None
        assert ts.ewma([(0.0, 2.0), (1.0, 4.0)], alpha=1.0) == 4.0

    def test_flap_counting(self):
        samples = [(0.0, 1.0), (1.0, 0.0), (2.0, 0.0), (3.0, 1.0),
                   (4.0, 1.0), (5.0, 0.0)]
        assert ts.flaps(samples) == 3
        assert ts.flaps([(0.0, 1.0)] * 5) == 0

    def test_staleness_tracks_newest_sample(self):
        fs = ts.FleetSeries(window=8)
        fs.record("r0", "up", 1.0, t_s=10.0)
        fs.record("r0", "depth", 2.0, t_s=12.0)
        assert fs.staleness_s("r0", now=15.0) == pytest.approx(3.0)
        assert fs.staleness_s("missing", now=15.0) is None

    def test_env_knobs_fall_back_on_malformed(self, monkeypatch):
        monkeypatch.setenv(ts.FLEET_TICK_MS_ENV, "not-a-number")
        monkeypatch.setenv(ts.FLEET_WINDOW_ENV, "-3")
        assert ts.env_tick_s() == ts.DEFAULT_TICK_MS / 1e3
        assert ts.env_window() == ts.DEFAULT_WINDOW
        monkeypatch.setenv(ts.FLEET_TICK_MS_ENV, "250")
        monkeypatch.setenv(ts.FLEET_WINDOW_ENV, "16")
        assert ts.env_tick_s() == pytest.approx(0.25)
        assert ts.env_window() == 16


class TestFleetSignals:
    def test_facade_records_and_snapshot_embeds_fleet(self, telemetry):
        obs.fleet_record("r0", "depth", 3.0, t_s=1.0)
        obs.fleet_record("r0", "depth", 5.0, t_s=2.0)
        obs.fleet_series().tick()
        snap = obs.snapshot()
        assert snap["fleet"]["ticks"] == 1
        assert snap["fleet"]["series"]["r0"]["depth"][-1] == [2.0, 5.0]
        obs.reset()
        assert obs.snapshot()["fleet"]["series"] == {}

    def test_fleet_record_is_noop_while_disabled(self):
        obs.disable()
        obs.reset()
        obs.fleet_record("r0", "depth", 1.0, t_s=0.0)
        assert obs.fleet_series().samples("r0", "depth") == []

    def test_signals_typed_bundle_from_sources(self, telemetry):
        store = obs.fleet_series()
        store.tick_s = 0.05
        now = 100.0
        for t in (now - 0.2, now - 0.1, now):
            obs.fleet_record("r0", "up", 1.0, t_s=t)
            obs.fleet_record("r0", "healthy", 1.0, t_s=t)
            obs.fleet_record("r0", "depth", 2.0, t_s=t)
            obs.fleet_record("r1", "up", 0.0, t_s=t)
            store.tick()
        obs.fleet_record("r0", "breaker_open", 1.0, t_s=now)
        obs.gauge("serve.goodput", 0.9, op="sosfilt", bucket=512)
        obs.count("serve_useful_rows", 90, op="sosfilt", bucket=512)
        obs.count("serve_dispatched_rows", 100, op="sosfilt",
                  bucket=512)
        obs.count("fleet_scrape_stale", replica="r9")
        obs.fleet_record("r0", "birth_age_s", 12.5, t_s=now)
        sig = ts.FleetSignals.from_sources(
            store, obs.snapshot(), obs.slo_snapshot(), now=now,
            scaler={"armed": True, "ticks": 7, "actions": {}})
        assert sig.health["r0"] == "healthy"
        assert sig.health["r1"] == "down"
        assert sig.queue_depth["r0"] == 2.0
        assert sig.breaker_open["r0"] == 1.0
        assert sig.goodput_overall == pytest.approx(0.9)
        assert list(sig.goodput.values()) == [pytest.approx(0.9)]
        assert sig.scrape_stale == {"r9": 1}
        assert sig.staleness_s["r0"] == pytest.approx(0.0)
        # obs v7: membership counts derived from health when no
        # collector replica_count_* series exists (hand-wired store),
        # per-replica birth ages, and the scaler summary pass-through
        assert sig.replica_count == {"up": 1, "draining": 0,
                                     "down": 1}
        assert sig.birth_age_s["r0"] == pytest.approx(12.5)
        assert sig.scaler["armed"] is True
        assert sig.scaler["ticks"] == 7
        d = sig.to_dict()
        assert d["schema"] == ts.SIGNALS_SCHEMA == \
            "veles-simd-signals-v4"
        assert d["health"]["r1"] == "down"
        assert d["replica_count"]["up"] == 1
        assert "series" in d
        # kwargs are checked: a typo'd signal name is a TypeError,
        # not a silently-absorbed attribute
        with pytest.raises(TypeError):
            ts.FleetSignals(not_a_signal=1)

    def test_signals_health_goes_stale_without_samples(self, telemetry):
        store = obs.fleet_series()
        store.tick_s = 0.05
        obs.fleet_record("r0", "up", 1.0, t_s=0.0)
        obs.fleet_record("r0", "healthy", 1.0, t_s=0.0)
        store.tick()
        # newest sample is 10 s old on a 50 ms tick: stale, not healthy
        sig = ts.FleetSignals.from_sources(
            store, obs.snapshot(), obs.slo_snapshot(), now=10.0)
        assert sig.health["r0"] == "stale"
        assert sig.staleness_s["r0"] == pytest.approx(10.0)


class _FakeTrace:
    def __init__(self, t0, rid, op, status, deadline_s, events):
        self._t0 = t0
        self.rid = rid
        self.op = op
        self.status = status
        self.deadline_s = deadline_s
        self._events = events

    def events(self):
        return list(self._events)


class _FakeTicket:
    rid = 7
    op = "sosfilt"
    status = "ok"
    failovers = 1
    replica = "r2"

    def __init__(self):
        self.prior_traces = [_FakeTrace(
            100.0, 7, "sosfilt", "failover", 0.5,
            [{"event": "submitted", "t_s": 0.0},
             {"event": "failover", "t_s": 0.01, "to": "r2"}])]
        self.trace = _FakeTrace(
            100.012, 7, "sosfilt", "ok", 0.488,
            [{"event": "submitted", "t_s": 0.0},
             {"event": "completed", "t_s": 0.02}])
        self.attempt_replicas = ["r0", "r2"]
        self.deadlines_ms = [500.0, 488.0]


class TestStitchFleetTrace:
    def test_two_attempt_stitch(self):
        doc = ts.stitch_fleet_trace(_FakeTicket())
        meta = doc["otherData"]
        assert meta["fleet"] is True
        assert meta["attempts"] == 2
        assert meta["replicas"] == ["r0", "r2"]
        # the carried-deadline proof rides along, only ever shrinking
        assert meta["deadlines_ms"] == [500.0, 488.0]
        evs = doc["traceEvents"]
        # one complete (X) span per attempt, on its own track
        spans = [e for e in evs if e["ph"] == "X"]
        assert [e["tid"] for e in spans] == [1, 2]
        assert spans[0]["args"]["replica"] == "r0"
        assert spans[1]["args"]["replica"] == "r2"
        # attempts align on the shared monotonic clock: the second
        # track starts 12 ms after the first
        assert spans[1]["ts"] - spans[0]["ts"] == \
            pytest.approx(0.012e6)
        # exactly one failover hop, at the dead attempt's terminal
        # edge, naming both sides
        hops = [e for e in evs if e["name"] == "failover_hop"]
        assert len(hops) == 1
        assert hops[0]["tid"] == 1
        assert hops[0]["args"]["from_replica"] == "r0"
        assert hops[0]["args"]["to_replica"] == "r2"
        # every lifecycle edge of both attempts is visible
        names = {(e["tid"], e["name"]) for e in evs
                 if e["ph"] == "i" and e["name"] != "failover_hop"}
        assert (1, "submitted") in names and (1, "failover") in names
        assert (2, "submitted") in names and (2, "completed") in names

    def test_save_trace_fleet_writes_stitched_doc(self, telemetry,
                                                  tmp_path):
        path = tmp_path / "fleet.json"
        obs.save_trace(str(path), fleet=_FakeTicket())
        doc = json.loads(path.read_text())
        assert doc["otherData"]["fleet"] is True
        assert doc["otherData"]["attempts"] == 2
        # an already-stitched dict is written verbatim
        obs.save_trace(str(path), fleet={"traceEvents": [],
                                         "otherData": {"fleet": True}})
        assert json.loads(path.read_text())["traceEvents"] == []
