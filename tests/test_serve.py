"""The serving layer (``veles/simd_tpu/serve/``).

Covers the four robustness pillars end to end on the virtual CPU mesh:
deadline batching (coalescing + bounded wait), admission control
(typed ``Overloaded``, per-tenant and global bounds, backpressure),
the fault-driven health machine (injected device loss -> bounded retry
-> DEGRADED oracle serving with parity -> probed recovery), and the
concurrency contract (no request lost, none double-answered).  The
chaos runs are driven by ``VELES_SIMD_FAULT_PLAN`` through the
``serve.dispatch`` / ``serve.admission`` injection sites — CPU CI, no
monkeypatching — with ``tools/loadgen.py`` as the traffic source for
the full overload + device-loss gate.
"""

import sys
import threading
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import loadgen  # noqa: E402
from veles.simd_tpu import obs, serve  # noqa: E402
from veles.simd_tpu.ops import iir  # noqa: E402
from veles.simd_tpu.ops import resample as rs  # noqa: E402
from veles.simd_tpu.ops import spectral as sp  # noqa: E402
from veles.simd_tpu.runtime import faults  # noqa: E402

RNG = np.random.RandomState(42)
SOS = iir.butterworth(4, 0.25, "lowpass")


@pytest.fixture
def telemetry(monkeypatch):
    """Telemetry on, zero retry backoff (deterministic), clean plans
    and metrics before/after."""
    monkeypatch.setenv("VELES_SIMD_FAULT_BACKOFF", "0")
    obs.enable(compile_listeners=False)
    obs.reset()
    faults.reset_fault_history()
    yield
    obs.disable()
    obs.reset()
    faults.reset_fault_history()
    faults.set_fault_plan(None)


def _rel(got, want):
    got = np.asarray(got, np.complex128)
    want = np.asarray(want, np.complex128)
    scale = float(np.max(np.abs(want))) or 1.0
    return float(np.max(np.abs(got - want)) / scale)


def _signal(n):
    return RNG.randn(n).astype(np.float32)


# ---------------------------------------------------------------------------
# request validation + ticket contract
# ---------------------------------------------------------------------------

class TestSubmitContract:
    def test_unsupported_op_raises(self):
        srv = serve.Server()
        with pytest.raises(ValueError, match="unsupported op"):
            srv.submit(serve.Request("fft9000", _signal(64)))

    def test_non_1d_signal_raises(self):
        srv = serve.Server()
        with pytest.raises(ValueError, match="1-D"):
            srv.submit(serve.Request("sosfilt", np.zeros((2, 64)),
                                     {"sos": SOS}))

    def test_stft_shorter_than_frame_raises(self):
        srv = serve.Server()
        with pytest.raises(ValueError):
            srv.submit(serve.Request(
                "stft", _signal(64),
                {"frame_length": 128, "hop": 64}))

    def test_unstarted_server_times_out_not_loses(self):
        srv = serve.Server(max_wait_ms=1.0)
        t = srv.submit(serve.Request("sosfilt", _signal(128),
                                     {"sos": SOS}))
        with pytest.raises(TimeoutError):
            t.result(timeout=0.05)
        assert not t.done()

    def test_submit_after_stop_raises(self):
        srv = serve.Server()
        srv.start()
        srv.stop()
        with pytest.raises(serve.ServerClosed):
            srv.submit(serve.Request("sosfilt", _signal(128),
                                     {"sos": SOS}))


# ---------------------------------------------------------------------------
# batching policy: coalescing + the deadline bound
# ---------------------------------------------------------------------------

class TestBatchingPolicy:
    def test_same_class_requests_coalesce(self):
        with serve.Server(max_batch=4, max_wait_ms=60.0,
                          workers=1) as srv:
            xs = [_signal(500) for _ in range(4)]
            ts = [srv.submit(serve.Request("sosfilt", x,
                                           {"sos": SOS}))
                  for x in xs]
            outs = [t.result(timeout=120.0) for t in ts]
        assert srv.stats()["counts"]["batches"] == 1
        for x, y in zip(xs, outs):
            assert _rel(y, iir.sosfilt_na(SOS, x[None, :])[0]) < 2e-4

    def test_deadline_answers_partial_batch(self):
        # one lone request in a 64-wide batch must still be answered:
        # the max_wait deadline fires, not the full-batch trigger
        with serve.Server(max_batch=64, max_wait_ms=20.0,
                          workers=1) as srv:
            t = srv.submit(serve.Request("sosfilt", _signal(256),
                                         {"sos": SOS}))
            y = t.result(timeout=120.0)
        assert t.status == "ok"
        assert y.shape == (256,)
        # observed wait = deadline + dispatch (compile included on the
        # first call); it must be bounded, not a full-batch starve
        assert t.wait_s is not None and t.wait_s < 60.0

    def test_distinct_shape_classes_do_not_mix(self):
        with serve.Server(max_batch=8, max_wait_ms=5.0,
                          workers=1) as srv:
            a = _signal(500)    # pow2 bucket 512
            b = _signal(900)    # pow2 bucket 1024
            ta = srv.submit(serve.Request("sosfilt", a, {"sos": SOS}))
            tb = srv.submit(serve.Request("sosfilt", b, {"sos": SOS}))
            ya, yb = (ta.result(timeout=120.0),
                      tb.result(timeout=120.0))
        assert srv.stats()["counts"]["batches"] == 2
        assert ya.shape == (500,) and yb.shape == (900,)
        assert _rel(ya, iir.sosfilt_na(SOS, a[None, :])[0]) < 2e-4
        assert _rel(yb, iir.sosfilt_na(SOS, b[None, :])[0]) < 2e-4

    def test_bucket_padding_is_exact_for_every_op(self):
        # non-pow2 lengths exercise the pad-to-bucket + slice-back
        # path against the unpadded single-call oracle
        n = 777
        x = _signal(n)
        cases = [
            ("sosfilt", {"sos": SOS},
             lambda: iir.sosfilt_na(SOS, x[None, :])[0]),
            ("lfilter", {"b": [0.2, 0.3, 0.1], "a": [1.0, -0.4]},
             lambda: iir.lfilter_na([0.2, 0.3, 0.1], [1.0, -0.4],
                                    x[None, :])[0]),
            ("resample_poly", {"up": 3, "down": 2},
             lambda: rs.resample_poly_na(x, 3, 2)),
            ("stft", {"frame_length": 128, "hop": 64},
             lambda: sp.stft_na(x, 128, 64)),
        ]
        with serve.Server(max_batch=4, max_wait_ms=5.0) as srv:
            for op, params, oracle in cases:
                t = srv.submit(serve.Request(op, x, params))
                assert _rel(t.result(timeout=300.0), oracle()) < 2e-3

    def test_padding_rows_counted_without_request_axis(self,
                                                       telemetry):
        # goodput accounting is a METRIC-axis write: it must record
        # even with the request axis disarmed (the low-overhead
        # production posture) — 3 coalesced rows pad to a pow2 batch
        # of 4, so one padding row, goodput 0.75
        obs.configure(request_axis=False)
        try:
            with serve.Server(max_batch=4, max_wait_ms=60.0,
                              workers=1) as srv:
                xs = [_signal(500) for _ in range(3)]
                ts = [srv.submit(serve.Request("sosfilt", x,
                                               {"sos": SOS}))
                      for x in xs]
                for t in ts:
                    t.result(timeout=120.0)
                good = srv.goodput()
                stats = srv.stats()
        finally:
            obs.configure(request_axis=True)
        snap = obs.snapshot()

        def counter(name):
            return sum(c["value"] for c in snap["counters"]
                       if c["name"] == name
                       and c["labels"].get("op") == "sosfilt"
                       and c["labels"].get("bucket") == "512")

        assert counter("serve_padding_rows") == 1
        assert counter("serve_useful_rows") == 3
        assert counter("serve_dispatched_rows") == 4
        gauges = {g["name"]: g["value"] for g in snap["gauges"]
                  if g["labels"].get("op") == "sosfilt"
                  and g["labels"].get("bucket") == "512"}
        assert gauges["serve.goodput"] == pytest.approx(0.75)
        assert gauges["serve.padding_waste"] == pytest.approx(0.25)
        # the server-side roll-up agrees, per class and overall
        assert good["sosfilt|512"]["useful_rows"] == 3
        assert good["sosfilt|512"]["dispatched_rows"] == 4
        assert good["sosfilt|512"]["goodput"] == pytest.approx(0.75)
        assert good["overall"]["goodput"] == pytest.approx(0.75)
        assert stats["goodput"]["overall"]["goodput"] == \
            pytest.approx(0.75)
        assert srv.counts()["useful_rows"] == 3
        assert srv.counts()["dispatched_rows"] == 4


# ---------------------------------------------------------------------------
# admission control + backpressure
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_bounds_and_typed_overloaded(self):
        ac = serve.AdmissionController(max_depth=3,
                                       max_tenant_depth=2)
        ac.admit("a")
        ac.admit("a")
        with pytest.raises(serve.Overloaded) as ei:
            ac.admit("a")
        assert ei.value.scope == "tenant"
        assert faults.is_overload(ei.value)
        ac.admit("b")
        with pytest.raises(serve.Overloaded) as ei:
            ac.admit("c")
        assert ei.value.scope == "global"
        ac.release("a")
        ac.admit("c")           # freed slot readmits
        snap = ac.snapshot()
        assert snap["depth"] == 3 and snap["shed"] == 2

    def test_backpressure_blocks_until_release(self):
        ac = serve.AdmissionController(max_depth=1,
                                       max_tenant_depth=1)
        ac.admit("a")
        done = threading.Event()

        def blocked():
            ac.admit("a", block=True, timeout=30.0)
            done.set()

        t = threading.Thread(target=blocked)
        t.start()
        assert not done.wait(0.05)      # genuinely parked
        ac.release("a")
        assert done.wait(5.0)           # woke and admitted
        t.join()

    def test_backpressure_deadline_expires_typed(self):
        ac = serve.AdmissionController(max_depth=1,
                                       max_tenant_depth=1)
        ac.admit("a")
        with pytest.raises(serve.Overloaded) as ei:
            ac.admit("a", block=True, timeout=0.05)
        assert ei.value.scope == "deadline"

    def test_injected_overload_sheds_deterministically(self,
                                                       telemetry):
        with faults.fault_plan("serve.admission:overload:2"):
            with serve.Server(max_batch=2, max_wait_ms=5.0) as srv:
                ts = [srv.submit(serve.Request(
                    "sosfilt", _signal(256), {"sos": SOS}))
                    for _ in range(4)]
                statuses = []
                for t in ts:
                    try:
                        t.result(timeout=120.0)
                        statuses.append(t.status)
                    except serve.Overloaded as e:
                        assert e.scope == "injected"
                        statuses.append(t.status)
        assert statuses[:2] == ["shed", "shed"]
        assert statuses[2:] == ["ok", "ok"]
        assert obs.counter_value("serve_shed", tenant="default",
                                 scope="injected") == 2


# ---------------------------------------------------------------------------
# fault-driven health machine
# ---------------------------------------------------------------------------

class TestHealthMachine:
    def test_degrade_parity_then_probed_recovery(self, telemetry):
        # 3 injected device losses = 1 guarded dispatch's full budget
        # (retries default 2) -> trip.  probe_every=2: batch 2 serves
        # oracle, batch 3 probes (plan empty) and recovers.
        with faults.fault_plan("serve.dispatch:device_lost:3"):
            with serve.Server(max_batch=1, max_wait_ms=2.0,
                              workers=1, probe_every=2) as srv:
                xs = [_signal(256) for _ in range(3)]
                outs, statuses = [], []
                for x in xs:
                    t = srv.submit(serve.Request("sosfilt", x,
                                                 {"sos": SOS}))
                    outs.append(t.result(timeout=120.0))
                    statuses.append(t.status)
                health = srv.stats()["health"]
        assert statuses == ["degraded", "degraded", "ok"]
        # DEGRADED answers are the oracle's, so parity is exact-ish
        for x, y in zip(xs, outs):
            assert _rel(y, iir.sosfilt_na(SOS, x[None, :])[0]) < 2e-4
        assert health["state"] == serve.HEALTHY
        assert health["trips"] == 1 and health["recoveries"] == 1
        assert obs.counter_value("fault_exhausted",
                                 site="serve.dispatch",
                                 kind="device_lost") == 1
        assert obs.counter_value("serve_recovered",
                                 site="serve.dispatch") == 1
        decisions = [(e["op"], e["decision"]) for e in obs.events()]
        assert ("serve_health", "degrade") in decisions
        assert ("serve_health", "recover") in decisions

    def test_probe_failure_stays_degraded(self, telemetry):
        # enough injections to also eat the first probe (zero-retry):
        # 3 (trip) + 1 (probe) = 4; with probe_every=1 every degraded
        # batch probes, so batch 2 probes-and-fails, batch 3 recovers
        with faults.fault_plan("serve.dispatch:device_lost:4"):
            with serve.Server(max_batch=1, max_wait_ms=2.0,
                              workers=1, probe_every=1) as srv:
                statuses = []
                for _ in range(3):
                    t = srv.submit(serve.Request(
                        "sosfilt", _signal(256), {"sos": SOS}))
                    t.result(timeout=120.0)
                    statuses.append(t.status)
                health = srv.stats()["health"]
        assert statuses == ["degraded", "degraded", "ok"]
        assert health["trips"] == 2          # initial + failed probe
        assert health["recoveries"] == 1
        assert health["probes"] == 2


# ---------------------------------------------------------------------------
# concurrency: no request lost, none double-answered
# ---------------------------------------------------------------------------

class TestConcurrency:
    def test_producer_threads_mixed_classes(self, telemetry):
        n_threads, per_thread = 6, 12
        lengths = (256, 500)
        with serve.Server(max_batch=8, max_wait_ms=5.0,
                          workers=2, queue_depth=4096,
                          tenant_depth=4096) as srv:
            all_tickets = [[] for _ in range(n_threads)]
            payloads = [[] for _ in range(n_threads)]

            def producer(slot):
                rng = np.random.RandomState(slot)
                for i in range(per_thread):
                    x = rng.randn(
                        lengths[i % len(lengths)]).astype(np.float32)
                    t = srv.submit(serve.Request(
                        "sosfilt", x, {"sos": SOS},
                        tenant=f"t{slot}"))
                    payloads[slot].append(x)
                    all_tickets[slot].append(t)

            threads = [threading.Thread(target=producer, args=(s,))
                       for s in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            flat = [(x, tk) for xs, tks in zip(payloads, all_tickets)
                    for x, tk in zip(xs, tks)]
            outs = [(x, tk, tk.result(timeout=300.0))
                    for x, tk in flat]
        # every request answered exactly once, none lost
        assert len(outs) == n_threads * per_thread
        assert all(tk.done() for _, tk, _ in outs)
        assert obs.counter_value("serve_double_answer") == 0
        assert srv.stats()["counts"]["completed"] == len(outs)
        # deadline batching bounded every observed wait
        assert all(tk.wait_s is not None and tk.wait_s < 120.0
                   for _, tk, _ in outs)
        # spot parity across producers
        for x, _, y in outs[:: len(outs) // 8 or 1]:
            assert _rel(y, iir.sosfilt_na(SOS, x[None, :])[0]) < 2e-4
        # admission fully drained back to zero
        assert srv.stats()["admission"]["depth"] == 0


# ---------------------------------------------------------------------------
# the chaos gate: loadgen overload + device loss, full accounting
# ---------------------------------------------------------------------------

class TestChaosGate:
    def test_overload_and_device_loss_full_accounting(self,
                                                      telemetry):
        rng = np.random.RandomState(7)
        schedule = loadgen.build_schedule(rng, 48, rate_hz=0.0,
                                          burst_every=0, burst_size=0)
        plan = ("serve.dispatch:device_lost:3,"
                "serve.admission:overload:4")
        with faults.fault_plan(plan):
            with serve.Server(max_batch=4, max_wait_ms=5.0,
                              workers=2, probe_every=2) as srv:
                report = loadgen.run_load(srv, schedule, verify=10,
                                          result_timeout=300.0,
                                          rng=rng)
                health = srv.stats()["health"]
        # zero lost, zero double-answered, typed sheds, parity holds
        assert report["lost"] == 0
        assert report["double_answered"] == 0
        assert report["parity_failures"] == 0
        assert report["shed"] == 4
        assert report["degraded"] >= 1
        assert (report["ok"] + report["degraded"]
                + report["shed"] == report["requests"])
        # DEGRADED -> recovered
        assert health["trips"] >= 1 and health["recoveries"] >= 1
        assert health["state"] == serve.HEALTHY
        # the obs snapshot carries the whole story: shed/retry/degrade
        # counters and p99 span quantiles for the serve spans
        snap = obs.snapshot()
        counters = {(c["name"], tuple(sorted(c["labels"].items()))):
                    c["value"] for c in snap["counters"]}
        total = {}
        for (name, _), v in counters.items():
            total[name] = total.get(name, 0) + v
        assert total.get("serve_shed", 0) == 4
        assert total.get("fault_retry", 0) >= 1
        assert total.get("fault_degraded", 0) >= 1
        assert total.get("serve_degraded", 0) >= 1
        assert total.get("serve_recovered", 0) >= 1
        qs = obs.quantiles("span.serve.dispatch", phase="steady")
        assert qs is not None and qs["p99"] is not None
        assert any(h["name"] == "serve.request_latency"
                   for h in snap["histograms"])


# ---------------------------------------------------------------------------
# loadgen bench-row surface (what `make bench-serve` gates on)
# ---------------------------------------------------------------------------

def test_latency_histogram_has_no_survivorship_bias(telemetry):
    """obs v4 satellite: shed and expired requests land in
    serve.request_latency with their own status labels — p99 can no
    longer understate tail pain by only counting batch-completed
    requests."""
    with serve.Server(max_batch=8, max_wait_ms=200.0, workers=1,
                      queue_depth=64) as srv:
        ok = srv.submit(serve.Request("sosfilt", _signal(256),
                                      {"sos": SOS}))
        ok.result(timeout=30.0)
        expired = srv.submit(serve.Request("sosfilt", _signal(256),
                                           {"sos": SOS}),
                             deadline_ms=1e-4)
        with pytest.raises(serve.DeadlineExceeded):
            expired.result(timeout=30.0)
    with faults.fault_plan("serve.admission:overload:1"):
        with serve.Server(max_batch=8, max_wait_ms=1.0,
                          workers=1) as srv:
            shed = srv.submit(serve.Request("sosfilt", _signal(256),
                                            {"sos": SOS}))
            assert shed.status == "shed"
    by_status = {h["labels"]["status"]: h["count"]
                 for h in obs.snapshot()["histograms"]
                 if h["name"] == "serve.request_latency"
                 and h["labels"].get("op") == "sosfilt"}
    assert by_status.get("ok", 0) >= 1
    assert by_status.get("expired", 0) == 1
    assert by_status.get("shed", 0) == 1
    # the counter twin carries the same status axis
    assert obs.counter_value("serve_completed", op="sosfilt",
                             status="expired") == 1
    assert obs.counter_value("serve_completed", op="sosfilt",
                             status="shed") == 1


def test_stop_nodrain_closes_traces_with_terminal_edge(telemetry):
    """PR 13 satellite: ``stop(drain=False)`` abandons queued work —
    but every abandoned ticket must still answer typed (``closed``),
    close its request trace with a terminal edge, and land in
    ``serve.request_latency{status=closed}``, so the
    ``zero_orphaned_traces`` invariant holds outside chaos campaigns
    too."""
    with_worker = serve.Server(max_batch=32, max_wait_ms=60000.0,
                               workers=1)
    with_worker.start()
    tickets = [with_worker.submit(serve.Request(
        "sosfilt", _signal(256), {"sos": SOS})) for _ in range(3)]
    with_worker.stop(drain=False)
    for t in tickets:
        assert t.status == "closed"
        with pytest.raises(serve.ServerClosed):
            t.result(timeout=1.0)
        assert t.trace.status == "closed"       # terminal edge
        assert t.trace.events()[-1]["event"] in ("closed", "error")
    by_status = {h["labels"]["status"]: h["count"]
                 for h in obs.snapshot()["histograms"]
                 if h["name"] == "serve.request_latency"
                 and h["labels"].get("op") == "sosfilt"}
    assert by_status.get("closed", 0) == 3
    # admission slots released: the queue is genuinely empty
    assert with_worker._admission.depth() == 0


def test_stop_nodrain_unstarted_server_loses_nothing(telemetry):
    """The regression that motivated the satellite: a server stopped
    before (or without) ``start()`` has NO worker to answer the
    abandoned queue — the stop path itself must sweep it, or the
    tickets hang forever with open traces."""
    srv = serve.Server(max_wait_ms=1.0)
    tickets = [srv.submit(serve.Request(
        "sosfilt", _signal(128), {"sos": SOS})) for _ in range(4)]
    srv.stop(drain=False)
    for t in tickets:
        assert t.done() and t.status == "closed"
        assert t.trace.status == "closed"
    assert srv._admission.depth() == 0
    # drain=True on an unstarted server must sweep too (nobody will
    # ever answer): typed closed, not a hang
    srv2 = serve.Server(max_wait_ms=1.0)
    t2 = srv2.submit(serve.Request("sosfilt", _signal(128),
                                   {"sos": SOS}))
    srv2.stop(drain=True)
    assert t2.done() and t2.status == "closed"


def test_obs_port_conflict_raises_typed_at_start(telemetry):
    """PR 13 satellite: two servers arming one port must fail at
    ``start()`` with a typed, actionable error — not die later in the
    serving thread — and leave the loser fully un-started."""
    from veles.simd_tpu.obs import http as obs_http

    first = serve.Server(max_wait_ms=1.0, obs_port=0).start()
    try:
        second = serve.Server(max_wait_ms=1.0,
                              obs_port=first.obs_port)
        with pytest.raises(obs_http.EndpointUnavailable) as ei:
            second.start()
        assert ei.value.port == first.obs_port
        assert "obs_port=0" in str(ei.value)    # actionable
        assert not second._started
        assert second._threads == []
        # the loser recovers on a free port
        second._obs_port_arg = 0
        second.start()
        assert second.obs_port not in (None, first.obs_port)
        second.stop()
    finally:
        first.stop()


def test_loadgen_bench_rows_shape(telemetry):
    report = {"throughput_rps": 123.4, "wait_p99_s": 0.02}
    rows = loadgen.bench_rows(report)
    metrics = [r["metric"] for r in rows]
    assert "serve throughput" in metrics
    assert "serve p99 inverse latency" in metrics
    for r in rows:
        assert set(r) >= {"metric", "value", "unit"}
    inv = next(r for r in rows
               if r["metric"] == "serve p99 inverse latency")
    assert inv["value"] == 50.0


# ---------------------------------------------------------------------------
# end-to-end request deadlines (PR 10)
# ---------------------------------------------------------------------------

class TestDeadlines:
    def test_expired_request_is_shed_typed_before_dispatch(
            self, telemetry):
        """A queued request whose deadline passes is answered with a
        typed DeadlineExceeded and its batch never dispatches."""
        with serve.Server(max_batch=8, max_wait_ms=500.0,
                          workers=1) as srv:
            t = srv.submit(serve.Request("sosfilt", _signal(256),
                                         {"sos": SOS}),
                           deadline_ms=15.0)
            with pytest.raises(serve.DeadlineExceeded) as ei:
                t.result(timeout=30.0)
            batches = srv.stats()["counts"]["batches"]
        assert t.status == "expired"
        # the typed answer classifies as a timeout for callers using
        # the engine's string classifiers across process boundaries
        assert faults.is_timeout(ei.value)
        assert batches == 0     # stale work never reached the device
        assert obs.counter_value("serve_deadline_miss", op="sosfilt",
                                 tenant="default") == 1
        assert srv.stats()["counts"]["expired"] == 1
        assert srv.stats()["admission"]["depth"] == 0

    def test_head_of_line_expiry_does_not_wedge_bucket(
            self, telemetry):
        """An expired head must be shed and readiness re-evaluated:
        the surviving request is answered on ITS constraints, not
        dispatched early with stale work and not starved behind it."""
        with serve.Server(max_batch=8, max_wait_ms=150.0,
                          workers=1) as srv:
            t1 = srv.submit(serve.Request("sosfilt", _signal(256),
                                          {"sos": SOS}),
                            deadline_ms=10.0)
            t2 = srv.submit(serve.Request("sosfilt", _signal(256),
                                          {"sos": SOS}),
                            deadline_ms=5000.0)
            with pytest.raises(serve.DeadlineExceeded):
                t1.result(timeout=30.0)
            y2 = t2.result(timeout=120.0)
        assert t1.status == "expired"
        assert t2.status == "ok"
        assert y2.shape == (256,)
        assert srv.stats()["counts"]["batches"] == 1

    def test_fully_expired_bucket_dispatches_nothing(self, telemetry):
        with serve.Server(max_batch=8, max_wait_ms=300.0,
                          workers=1) as srv:
            ts = [srv.submit(serve.Request("sosfilt", _signal(256),
                                           {"sos": SOS}),
                             deadline_ms=10.0) for _ in range(4)]
            for t in ts:
                with pytest.raises(serve.DeadlineExceeded):
                    t.result(timeout=30.0)
            assert srv.stats()["counts"]["batches"] == 0
            assert srv.stats()["counts"]["expired"] == 4

    def test_env_default_deadline(self, telemetry, monkeypatch):
        monkeypatch.setenv(serve.DEADLINE_ENV, "15")
        assert serve.env_deadline_ms() == 15.0
        with serve.Server(max_batch=8, max_wait_ms=500.0,
                          workers=1) as srv:
            t = srv.submit(serve.Request("sosfilt", _signal(256),
                                         {"sos": SOS}))
            with pytest.raises(serve.DeadlineExceeded):
                t.result(timeout=30.0)
        assert t.status == "expired"

    def test_deadline_under_fault_storm_is_answered_in_budget(
            self, telemetry, monkeypatch):
        """The acceptance criterion: a short-deadline request
        submitted into a transient-fault storm with a huge retry
        allowance is answered (typed/degraded) within deadline + one
        backoff quantum — the guarded retry loop is clipped to the
        request budget."""
        monkeypatch.setenv("VELES_SIMD_FAULT_BACKOFF", "0.02")
        monkeypatch.setenv("VELES_SIMD_FAULT_RETRIES", "10000")
        with faults.fault_plan("serve.dispatch:device_lost:100000"):
            with serve.Server(max_batch=1, max_wait_ms=2.0,
                              workers=1) as srv:
                t0 = faults.monotonic()
                t = srv.submit(serve.Request("sosfilt", _signal(256),
                                             {"sos": SOS}),
                               deadline_ms=150.0)
                y = t.result(timeout=30.0)
                elapsed = faults.monotonic() - t0
        assert t.status == "degraded"       # oracle answer, typed
        assert y.shape == (256,)
        # 150 ms budget + one backoff quantum + dispatch slop; without
        # clipping the 10000-retry ladder would run for minutes
        assert elapsed < 2.0
        assert obs.counter_value("fault_budget_clipped",
                                 site="serve.dispatch") == 1

    def test_deadline_slack_histogram_flows(self, telemetry):
        with serve.Server(max_batch=1, max_wait_ms=2.0,
                          workers=1) as srv:
            t = srv.submit(serve.Request("sosfilt", _signal(256),
                                         {"sos": SOS}),
                           deadline_ms=60000.0)
            t.result(timeout=120.0)
        snap = obs.snapshot()
        assert any(h["name"] == "serve.deadline_slack"
                   for h in snap["histograms"])


# ---------------------------------------------------------------------------
# continuous batching + ragged segment packing (PR 17)
# ---------------------------------------------------------------------------

class TestRaggedServe:
    def test_ragged_classing_is_env_gated(self, monkeypatch):
        params = {"frame_length": 128, "hop": 64}
        monkeypatch.setenv(serve.server.RAGGED_ENV, "0")
        *_, key = serve.server.classify_request(
            "stft", _signal(300), params)
        assert key == ("stft", (128, 64), 512)
        monkeypatch.setenv(serve.server.RAGGED_ENV, "1")
        *_, key = serve.server.classify_request(
            "stft", _signal(300), params)
        assert key == ("stft", (128, 64), "ragged")
        # heavy-tail requests keep their plain bucket: one long signal
        # must not inflate the packed width of co-packed short ones
        n_long = serve.server.ragged_max() + 1
        *_, key = serve.server.classify_request(
            "stft", _signal(n_long), params)
        assert key[-1] == serve.server.bucket_length(n_long)
        # non-stft ops never co-pack (IIR state threads along the row)
        *_, key = serve.server.classify_request(
            "sosfilt", _signal(300), {"sos": SOS})
        assert key[-1] == 512

    def test_ragged_parity_and_sample_accounting(self, telemetry,
                                                 monkeypatch):
        monkeypatch.setenv(serve.server.RAGGED_ENV, "1")
        lens = (200, 128, 513, 300)
        xs = [_signal(n) for n in lens]
        srv = serve.Server(max_batch=8, max_wait_ms=20.0, workers=1)
        # submit before start so ALL requests land in ONE ragged batch
        ts = [srv.submit(serve.Request(
            "stft", x, {"frame_length": 128, "hop": 64}))
            for x in xs]
        with srv:
            for t, x in zip(ts, xs):
                got = t.result(timeout=120.0)
                assert _rel(got, sp.stft_na(x, 128, 64)) < 2e-3
                assert t.status == "ok"
        snap = obs.snapshot()

        def counter(name):
            return sum(c["value"] for c in snap["counters"]
                       if c["name"] == name
                       and c["labels"].get("bucket") == "ragged")

        from veles.simd_tpu.ops import segments as _seg
        strides = [_seg.stft_stride(n, 64) for n in lens]
        width, rows, _ = _seg.plan_pack(strides)
        assert counter("serve_useful_samples") == sum(lens)
        assert counter("serve_dispatched_samples") == rows * width
        assert counter("serve_useful_rows") == rows
        assert counter("serve_dispatched_rows") == rows
        good = srv.goodput()
        ragged_keys = [k for k in good if k.endswith("|ragged")]
        assert ragged_keys, good
        gp = good[ragged_keys[0]]
        assert 0.0 < gp["sample_goodput"] <= 1.0
        assert gp["useful_samples"] == sum(lens)

    def test_ragged_fault_degrades_one_ticket_only(self, telemetry,
                                                   monkeypatch):
        monkeypatch.setenv(serve.server.RAGGED_ENV, "1")
        xs = [_signal(n) for n in (200, 128, 300)]
        faults.set_fault_plan(
            "segments.dispatch@stft:device_lost:3,"
            "segments.segment@1:device_lost:1")
        srv = serve.Server(max_batch=8, max_wait_ms=20.0, workers=1)
        ts = [srv.submit(serve.Request(
            "stft", x, {"frame_length": 128, "hop": 64}))
            for x in xs]
        with srv:
            vals = [t.result(timeout=120.0) for t in ts]
        # the poisoned segment degrades to its oracle; its co-packed
        # neighbors keep device answers and OK tickets
        assert [t.status for t in ts] == ["ok", "degraded", "ok"]
        for v, x in zip(vals, xs):
            assert _rel(v, sp.stft_na(x, 128, 64)) < 2e-3
        ev = [e["event"] for e in ts[1].trace.events()]
        assert "degraded" in ev

    def test_refill_rides_expiry_freed_slots(self, telemetry,
                                             monkeypatch):
        """An expired request swept out of a taken batch frees a row
        slot below the pow2 class; continuous batching refills it from
        the queue at dispatch time — the refilled ticket gets its own
        tagged batch_formed edge and every ticket answers exactly
        once.  The take->dispatch window is driven by hand (the worker
        loop hits it only under racy timing): take the full batch via
        the batcher, let one member's deadline lapse, queue the rider,
        then run the dispatch path directly."""
        monkeypatch.setenv(serve.server.CONTINUOUS_ENV, "1")
        srv = serve.Server(max_batch=4, max_wait_ms=20.0, workers=1)
        doomed = srv.submit(serve.Request(
            "sosfilt", _signal(400), {"sos": SOS}), deadline_ms=60.0)
        live = [srv.submit(serve.Request(
            "sosfilt", _signal(400), {"sos": SOS})) for _ in range(3)]
        # the class is full (4/4) -> instantly ready; doomed is still
        # live at take so the batcher does NOT shed it
        key, batch = srv._batcher.next_batch()
        assert len(batch) == 4
        rider = srv.submit(serve.Request(
            "sosfilt", _signal(400), {"sos": SOS}))
        import time as _time
        _time.sleep(0.2)  # doomed's deadline lapses post-take
        srv._run_batch(key, batch)
        with pytest.raises(serve.DeadlineExceeded):
            doomed.result(timeout=5.0)
        for t in live + [rider]:
            t.result(timeout=5.0)
            assert t.status == "ok"
        # the rider refilled the slot the expired request freed
        formed = [e for e in rider.trace.events()
                  if e["event"] == "batch_formed"]
        assert formed and formed[0].get("refilled") is True
        assert srv.stats()["counts"]["refilled_rows"] == 1
        # zero lost / zero double-answered: every ticket terminal once
        for t in [doomed] + live + [rider]:
            assert t.done()
        srv.stop()

    def test_refill_disabled_leaves_queue_untouched(self, telemetry,
                                                    monkeypatch):
        """Same freed-slot window with the flag off: the rider stays
        queued through the dispatch, then answers in its own later
        batch with an untagged batch_formed edge."""
        monkeypatch.setenv(serve.server.CONTINUOUS_ENV, "0")
        srv = serve.Server(max_batch=4, max_wait_ms=20.0, workers=1)
        live = [srv.submit(serve.Request(
            "sosfilt", _signal(400), {"sos": SOS})) for _ in range(3)]
        # 3/4: ready only once max_wait lapses, so the take is short
        key, batch = srv._batcher.next_batch()
        assert len(batch) == 3
        rider = srv.submit(serve.Request(
            "sosfilt", _signal(400), {"sos": SOS}))
        srv._run_batch(key, batch)
        for t in live:
            t.result(timeout=5.0)
            assert t.status == "ok"
        assert srv.stats()["counts"]["refilled_rows"] == 0
        assert not rider.done()
        # the worker pool answers the rider via its own batch
        with srv:
            rider.result(timeout=120.0)
            assert rider.status == "ok"
        formed = [e for e in rider.trace.events()
                  if e["event"] == "batch_formed"]
        assert formed and not formed[0].get("refilled")
