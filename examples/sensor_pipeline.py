#!/usr/bin/env python
"""End-to-end sensor conditioning: despike → detrend → filter → analyze.

One pass through the round-3 families on a realistic problem — a
vibration sensor whose trace carries a drifting baseline, salt spikes,
mains hum, and two structural resonances:

1. ``filters.medfilt``            kills the salt spikes (nonlinear),
2. ``spectral.detrend``           removes the baseline drift,
3. ``iir`` notch (bandstop)       removes the 50 Hz hum — zero-phase,
4. ``spectral.welch``             estimates the cleaned PSD,
5. ``filters.savgol_filter``      smooths the PSD for peak reading,
6. ``detect_peaks``               reads off the resonance frequencies.

Run:  python examples/sensor_pipeline.py
      VELES_SIMD_PLATFORM=cpu python examples/sensor_pipeline.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import maybe_override_platform

maybe_override_platform()

from veles.simd_tpu.ops import detect_peaks as dp  # noqa: E402
from veles.simd_tpu.ops import filters as fl  # noqa: E402
from veles.simd_tpu.ops import iir  # noqa: E402
from veles.simd_tpu.ops import spectral as sp  # noqa: E402


def main():
    fs = 2000.0
    n = 1 << 15
    rng = np.random.RandomState(7)
    t = np.arange(n) / fs

    resonances = (137.0, 310.0)
    x = sum(a * np.sin(2 * np.pi * f0 * t)
            for a, f0 in zip((1.0, 0.6), resonances))
    x = x + 1.5 * np.sin(2 * np.pi * 50.0 * t)       # mains hum
    x = x + 0.4 * t / t[-1] + 0.2                    # baseline drift
    x = x + 0.05 * rng.randn(n)                      # sensor noise
    spikes = rng.choice(n, 60, replace=False)
    x[spikes] = 30.0 * np.sign(rng.randn(60))        # dropouts
    x = x.astype(np.float32)

    # 1. despike; 2. detrend
    y = fl.medfilt(x, 5)
    y = sp.detrend(y, "linear")

    # 3. zero-phase 50 Hz notch
    notch = iir.butterworth(4, (44 / (fs / 2), 56 / (fs / 2)), "bandstop")
    y = iir.sosfiltfilt(notch, y)

    # 4. PSD of the cleaned trace; 5. smooth it
    f, pxx = sp.welch(y, fs=fs, nperseg=1024)
    pxx_db = 10 * np.log10(np.maximum(np.asarray(pxx), 1e-12))
    smooth = np.asarray(fl.savgol_filter(
        pxx_db.astype(np.float32), 7, 2))

    # 6. resonance read-off
    pos, vals, count = dp.detect_peaks_fixed(
        smooth, dp.ExtremumType.MAXIMUM, max_peaks=64)
    pos, vals = np.asarray(pos), np.asarray(vals)
    found = sorted(
        float(f[p]) for p, v in zip(pos[:int(count)], vals[:int(count)])
        if v > smooth.max() - 12.0)          # within 12 dB of the top
    print(f"resonances found: {[f'{v:.0f} Hz' for v in found]}")

    hum_bin = int(round(50.0 / (fs / 1024)))
    print(f"hum suppression: {pxx_db[hum_bin] - smooth.max():.0f} dB "
          "below the strongest resonance")

    ok = (len(found) == 2
          and all(abs(g - want) < fs / 1024 + 1e-9
                  for g, want in zip(found, resonances))
          and pxx_db[hum_bin] < smooth.max() - 20.0)
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
