"""Tests for veles.simd_tpu.ops.arithmetic.

Port of the reference's test strategy for ``tests/arithmetic.cc``
(SURVEY.md §4): XLA-vs-oracle cross-validation (the reference's
SIMD-vs-``_na`` pattern, ``tests/arithmetic.cc:223-239``), float16
golden values incl. inf/nan/subnormals/signed zero
(``tests/arithmetic.cc:335-415``), and contract-violation checks.
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import arithmetic as ar

RNG = np.random.RandomState(1234)


def assert_xla_matches_oracle(fn, *args, **kw):
    got = np.asarray(fn(*args, simd=True, **kw))
    want = fn(*args, simd=False, **kw)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert got.dtype == want.dtype


@pytest.mark.parametrize("length", [1, 3, 8, 509, 4096])
def test_int16_to_float(length):
    data = RNG.randint(-32768, 32768, size=length).astype(np.int16)
    assert_xla_matches_oracle(ar.int16_to_float, data)


@pytest.mark.parametrize("length", [1, 3, 8, 509, 4096])
def test_float_to_int16_truncates(length):
    data = (RNG.rand(length).astype(np.float32) - 0.5) * 65000
    assert_xla_matches_oracle(ar.float_to_int16, data)
    # truncation-not-rounding semantics (arithmetic.h:53-55)
    vals = np.array([1.9, -1.9, 0.5, -0.5, 32767.9, -32768.9], np.float32)
    np.testing.assert_array_equal(
        np.asarray(ar.float_to_int16(vals, simd=True)),
        np.array([1, -1, 0, 0, 32767, -32768], np.int16))


def test_float_to_int16_saturates():
    vals = np.array([1e9, -1e9, 40000.0, -40000.0], np.float32)
    np.testing.assert_array_equal(
        np.asarray(ar.float_to_int16(vals, simd=True)),
        np.array([32767, -32768, 32767, -32768], np.int16))


@pytest.mark.parametrize("length", [1, 3, 509])
def test_int32_roundtrips(length):
    i32 = RNG.randint(-(2**24), 2**24, size=length).astype(np.int32)
    assert_xla_matches_oracle(ar.int32_to_float, i32)
    f32 = (RNG.rand(length).astype(np.float32) - 0.5) * 1e6
    assert_xla_matches_oracle(ar.float_to_int32, f32)


@pytest.mark.parametrize("length", [1, 3, 509])
def test_int16_int32_widen_narrow(length):
    i16 = RNG.randint(-32768, 32768, size=length).astype(np.int16)
    assert_xla_matches_oracle(ar.int16_to_int32, i16)
    i32 = RNG.randint(-32768, 32768, size=length).astype(np.int32)
    assert_xla_matches_oracle(ar.int32_to_int16, i32)


def test_int32_to_int16_saturates():
    # vector-path saturating semantics (_mm_packs_epi32, arithmetic.h:334)
    vals = np.array([2**20, -(2**20), 32768, -32769, 5], np.int32)
    np.testing.assert_array_equal(
        np.asarray(ar.int32_to_int16(vals, simd=True)),
        np.array([32767, -32768, 32767, -32768, 5], np.int16))


class TestFloat16:
    """Golden float16 cases from tests/arithmetic.cc:335-415."""

    def check(self, bits, expected):
        bits = np.asarray(bits, np.uint16)
        got = np.asarray(ar.float16_to_float(bits, simd=True))
        want = ar.float16_to_float(bits, simd=False)
        np.testing.assert_array_equal(got, want)
        if expected is not None:
            np.testing.assert_array_equal(
                got, np.asarray(expected, np.float32))

    def test_normals(self):
        self.check([0x3C00, 0xC000, 0x4248], [1.0, -2.0, 3.140625])

    def test_signed_zero(self):
        got = np.asarray(ar.float16_to_float(
            np.array([0x0000, 0x8000], np.uint16), simd=True))
        np.testing.assert_array_equal(got, [0.0, -0.0])
        assert np.signbit(got[1]) and not np.signbit(got[0])

    def test_inf_nan(self):
        got = np.asarray(ar.float16_to_float(
            np.array([0x7C00, 0xFC00, 0x7E00], np.uint16), simd=True))
        assert got[0] == np.inf and got[1] == -np.inf and np.isnan(got[2])

    def test_subnormals(self):
        # smallest subnormal 2^-24, largest subnormal (1023/1024)*2^-14
        self.check([0x0001, 0x03FF, 0x8001],
                   [2.0 ** -24, (1023 / 1024) * 2.0 ** -14, -(2.0 ** -24)])

    def test_random_all_finite(self):
        bits = RNG.randint(0, 0x7C00, size=2048).astype(np.uint16)
        self.check(bits, None)

    def test_accepts_float16_array(self):
        x = np.array([1.5, -0.25], np.float16)
        np.testing.assert_array_equal(
            np.asarray(ar.float16_to_float(x, simd=True)), [1.5, -0.25])


@pytest.mark.parametrize("length", [4, 510])
def test_int16_multiply_widens(length):
    a = RNG.randint(-32768, 32768, size=length).astype(np.int16)
    b = RNG.randint(-32768, 32768, size=length).astype(np.int16)
    got = np.asarray(ar.int16_multiply(a, b, simd=True))
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, ar.int16_multiply(a, b, simd=False))
    # would overflow int16: check widening really happened
    big = np.array([-32768], np.int16)
    assert ar.int16_multiply(big, big, simd=True)[0] == 2 ** 30


@pytest.mark.parametrize("length", [2, 8, 512])
def test_real_multiply(length):
    a = RNG.rand(length).astype(np.float32)
    b = RNG.rand(length).astype(np.float32)
    assert_xla_matches_oracle(ar.real_multiply, a, b)


def test_real_multiply_scalar():
    a = RNG.rand(333).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ar.real_multiply_scalar(a, 2.5, simd=True)),
        ar.real_multiply_scalar(a, 2.5, simd=False), rtol=1e-7)


@pytest.mark.parametrize("n_complex", [1, 4, 256])
def test_complex_multiply(n_complex):
    a = RNG.randn(2 * n_complex).astype(np.float32)
    b = RNG.randn(2 * n_complex).astype(np.float32)
    assert_xla_matches_oracle(ar.complex_multiply, a, b)
    # against numpy complex arithmetic
    za = ar.deinterleave_complex(a)
    zb = ar.deinterleave_complex(b)
    np.testing.assert_allclose(
        np.asarray(ar.complex_multiply(a, b, simd=True)),
        ar.interleave_complex(za * zb), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n_complex", [1, 4, 256])
def test_complex_multiply_conjugate(n_complex):
    a = RNG.randn(2 * n_complex).astype(np.float32)
    b = RNG.randn(2 * n_complex).astype(np.float32)
    assert_xla_matches_oracle(ar.complex_multiply_conjugate, a, b)
    za = ar.deinterleave_complex(a)
    zb = ar.deinterleave_complex(b)
    np.testing.assert_allclose(
        np.asarray(ar.complex_multiply_conjugate(a, b, simd=True)),
        ar.interleave_complex(za * np.conj(zb)), rtol=1e-5, atol=1e-5)


def test_complex_conjugate():
    a = RNG.randn(64).astype(np.float32)
    assert_xla_matches_oracle(ar.complex_conjugate, a)


@pytest.mark.parametrize("length", [1, 7, 4096])
def test_sum_elements(length):
    data = RNG.rand(length).astype(np.float32)
    got = float(ar.sum_elements(data, simd=True))
    want = float(ar.sum_elements(data, simd=False))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_add_to_all():
    data = RNG.rand(100).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ar.add_to_all(data, 3.25, simd=True)),
        ar.add_to_all(data, 3.25, simd=False), rtol=1e-7)


def test_interleave_roundtrip():
    z = (RNG.randn(32) + 1j * RNG.randn(32)).astype(np.complex64)
    np.testing.assert_allclose(
        ar.deinterleave_complex(ar.interleave_complex(z)), z, rtol=1e-6)
